"""Graphboard: dataflow-graph visualization (reference
python/graphboard/graph2fig.py — renders the graph and serves it on a
local HTTP port).

TPU build renders the Op graph three ways:

- ``to_dot(nodes)``   — Graphviz DOT text,
- ``to_html(nodes)``  — standalone HTML page (embedded SVG-free force
  layout, no external assets: the image has no egress),
- ``show(executor, port)`` / ``close()`` — serve the HTML like the
  reference's `show` (graph2fig.py:11-30).
"""

from __future__ import annotations

import html
import json
import threading

from .graph.node import Op
from .graph.autodiff import find_topo_sort
from .graph.ops_misc import PlaceholderOp
from .optimizer import OptimizerOp

_httpd = None


def _collect(nodes_or_executor):
    if hasattr(nodes_or_executor, "eval_node_dict"):
        nodes = [n for ns in nodes_or_executor.eval_node_dict.values()
                 for n in ns]
    elif isinstance(nodes_or_executor, Op):
        nodes = [nodes_or_executor]
    else:
        nodes = list(nodes_or_executor)
    return find_topo_sort(nodes)


def _kind(node):
    if isinstance(node, OptimizerOp):
        return "optimizer"
    if isinstance(node, PlaceholderOp):
        return "variable" if node.is_variable else "placeholder"
    return "op"


_COLORS = {"op": "#BFDFFF", "placeholder": "#C6F7D0",
           "variable": "#FFE9A8", "optimizer": "#FFC4C4"}


def to_dot(nodes_or_executor, name="hetu_graph"):
    topo = _collect(nodes_or_executor)
    lines = [f'digraph "{name}" {{', "  rankdir=TB;",
             "  node [shape=box, style=filled, fontname=Helvetica];"]
    for n in topo:
        color = _COLORS[_kind(n)]
        label = n.name.replace('"', "'")
        shape = getattr(n, "shape", None)
        if shape:
            label += f"\\n{tuple(shape)}"
        lines.append(f'  n{n.id} [label="{label}", fillcolor="{color}"];')
    for n in topo:
        for i in n.inputs:
            lines.append(f"  n{i.id} -> n{n.id};")
    lines.append("}")
    return "\n".join(lines)


def to_html(nodes_or_executor, name="hetu_graph"):
    """Self-contained HTML: nodes laid out by topological depth with a
    tiny inline renderer (no CDN dependencies)."""
    topo = _collect(nodes_or_executor)
    depth = {}
    for n in topo:
        depth[n.id] = 1 + max((depth[i.id] for i in n.inputs), default=-1)
    data = {
        "name": name,
        "nodes": [{"id": n.id, "label": n.name, "kind": _kind(n),
                   "depth": depth[n.id]} for n in topo],
        "edges": [{"from": i.id, "to": n.id}
                  for n in topo for i in n.inputs],
    }
    payload = json.dumps(data)
    return f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>{html.escape(name)}</title>
<style>
body {{ font-family: Helvetica, sans-serif; margin: 0; }}
svg {{ width: 100vw; height: 100vh; }}
.node rect {{ stroke: #333; rx: 4; }}
.node text {{ font-size: 11px; }}
.edge {{ stroke: #999; fill: none; marker-end: url(#arr); }}
</style></head><body>
<svg id="g"><defs><marker id="arr" viewBox="0 0 10 10" refX="9" refY="5"
 markerWidth="6" markerHeight="6" orient="auto-start-reverse">
 <path d="M 0 0 L 10 5 L 0 10 z" fill="#999"/></marker></defs></svg>
<script>
const COLORS = {json.dumps(_COLORS)};
const data = {payload};
const byDepth = {{}};
data.nodes.forEach(n => (byDepth[n.depth] ||= []).push(n));
const W = 170, H = 46, pos = {{}};
Object.entries(byDepth).forEach(([d, ns]) => ns.forEach((n, i) => {{
  pos[n.id] = {{x: 40 + i * W, y: 40 + d * H * 1.6}};
}}));
const svg = document.getElementById('g');
const NS = 'http://www.w3.org/2000/svg';
data.edges.forEach(e => {{
  const a = pos[e.from], b = pos[e.to];
  const p = document.createElementNS(NS, 'path');
  p.setAttribute('class', 'edge');
  p.setAttribute('d', `M ${{a.x + 70}} ${{a.y + 30}} L ${{b.x + 70}} ${{b.y}}`);
  svg.appendChild(p);
}});
data.nodes.forEach(n => {{
  const g = document.createElementNS(NS, 'g');
  g.setAttribute('class', 'node');
  const r = document.createElementNS(NS, 'rect');
  const p = pos[n.id];
  r.setAttribute('x', p.x); r.setAttribute('y', p.y);
  r.setAttribute('width', 140); r.setAttribute('height', 30);
  r.setAttribute('fill', COLORS[n.kind]);
  const t = document.createElementNS(NS, 'text');
  t.setAttribute('x', p.x + 6); t.setAttribute('y', p.y + 19);
  t.textContent = n.label.slice(0, 22);
  g.appendChild(r); g.appendChild(t); svg.appendChild(g);
}});
const maxX = Math.max(...Object.values(pos).map(p => p.x)) + 220;
const maxY = Math.max(...Object.values(pos).map(p => p.y)) + 120;
svg.setAttribute('viewBox', `0 0 ${{maxX}} ${{maxY}}`);
</script></body></html>"""


def show(executor, port=9997):
    """Serve the executor's graph on http://localhost:port (reference
    graph2fig.show)."""
    global _httpd
    import http.server

    page = to_html(executor).encode("utf-8")

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(page)))
            self.end_headers()
            self.wfile.write(page)

        def log_message(self, *a):
            pass

    close()
    _httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=_httpd.serve_forever, daemon=True).start()
    return f"http://127.0.0.1:{port}"


def close():
    """Stop the server started by show() (reference graph2fig.close)."""
    global _httpd
    if _httpd is not None:
        _httpd.shutdown()
        _httpd.server_close()
        _httpd = None
