"""Shared persistence discipline for measurement artifacts.

One rule, applied by bench.py's sweep modes AND the planner's chip
calibration: a degraded run (reduced scale, or not on real TPU) never
overwrites a full-scale TPU record, and a run that produced no data
never overwrites a record that has some.  Centralized here so the two
consumers cannot drift (review r5: chip_calibration's hand copy had
already lost the reduced-scale half).
"""

from __future__ import annotations

import json
import os
import tempfile


def atomic_json_dump(path, obj, indent=1):
    """Write JSON via a same-directory temp file + os.replace: a
    process killed mid-write (the suite's per-stage timeouts SIGTERM
    bench.py wherever it is) must never leave a truncated record that
    a later run silently discards and overwrites."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=indent)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def persist_artifact(path, art, reduced, has_data=True):
    """Write ``art`` (a JSON-able dict) to ``path`` unless doing so
    would degrade the record:

    * ``reduced`` runs (small shapes, or a non-TPU backend) never
      replace an existing full-scale TPU record;
    * an all-error run (``has_data=False``) never replaces a record
      that has data.

    When skipped, sets ``art['not_written']`` with the reason and
    returns False; otherwise writes and returns True.
    """
    existing = None
    try:
        with open(path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        pass
    if isinstance(existing, dict):
        if (not existing.get("reduced_scale")
                and existing.get("platform") == "tpu" and reduced):
            art["not_written"] = ("full-scale TPU record already "
                                  "present; reduced run not persisted")
            return False
        if not has_data:
            art["not_written"] = ("run produced no measured data; "
                                  "keeping the existing record")
            return False
    atomic_json_dump(path, art)
    return True
