"""Collective-ordering validator for hand-written shard_map programs.

SURVEY.md §5.2: the reference has no sanitizer; stream races are prevented
structurally by events, and the deadlock risk lives in hand-paired
send/recv choreography (pipedream_subexecutor.py:257-290 group-call
deadlock avoidance).  On TPU the pjit path is safe by construction, but a
*hand-written* shard_map program can still deadlock or corrupt data when
different devices disagree on the collective sequence — the realistic way
that happens under SPMD is a ``lax.cond`` whose predicate depends on
``axis_index`` with a collective inside only one branch.

``check_collective_order(fn, mesh, in_specs, out_specs, example_args)``
traces the shard_map program (no execution) and

1. records the sequence of collective primitives with their axis/shape
   signatures, and
2. raises :class:`CollectiveOrderError` if any ``lax.cond`` branches
   disagree on the collectives they issue.

Run it in tests for every hand-written shard_map pipeline; it is cheap
(one trace, no compile).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# primitives that synchronize a mesh axis (includes the *_invariant
# spellings jax uses inside shard_map traces); pvary/replication markers
# are not synchronizing and are ignored
_COLLECTIVE_PRIMS = {
    "psum", "psum_invariant", "pmax", "pmin", "pmean", "all_gather",
    "all_gather_invariant", "all_to_all", "ppermute", "reduce_scatter",
    "psum_scatter", "pbroadcast",
    # pre-0.5 jax spells the shard_map-rewritten psum "psum2"
    "psum2",
}


class CollectiveOrderError(AssertionError):
    pass


def _axes_of(eqn):
    for key in ("axes", "axis_name", "axis_index_groups"):
        v = eqn.params.get(key)
        if v is not None:
            return str(v)
    return "?"


def _collect(closed_or_open, seq):
    """DFS a jaxpr recording collective signatures; verifies cond
    branches agree and recurses into scan/while/call bodies."""
    jaxpr = getattr(closed_or_open, "jaxpr", closed_or_open)
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "cond":
            subseqs = []
            for br in eqn.params["branches"]:
                s = []
                _collect(br, s)
                subseqs.append(s)
            for i, s in enumerate(subseqs[1:], 1):
                if s != subseqs[0]:
                    raise CollectiveOrderError(
                        "lax.cond branches disagree on collectives: "
                        f"branch 0 issues {subseqs[0] or 'none'}, "
                        f"branch {i} issues {s or 'none'} — a device "
                        "taking a different branch deadlocks the axis")
            seq.extend(subseqs[0])
            continue
        for key, v in eqn.params.items():
            if key == "branches":
                continue
            if hasattr(v, "jaxpr") or type(v).__name__ == "Jaxpr":
                _collect(v, seq)
        if prim in _COLLECTIVE_PRIMS:
            shapes = tuple(tuple(v.aval.shape) for v in eqn.invars
                           if hasattr(v, "aval"))
            # dtypes ride in the signature so a QUANTIZED collective
            # (int8 payload — the ops_comm quantize→gather→dequantize
            # pair) is distinguishable from its f32 twin: cond branches
            # disagreeing on quantized-vs-unquantized aggregation are a
            # sequence divergence like any other
            dtypes = tuple(str(v.aval.dtype) for v in eqn.invars
                           if hasattr(v, "aval"))
            seq.append((prim, _axes_of(eqn), shapes, dtypes))
    return seq


def quantized_collectives(seq):
    """The int8-carrying entries of a recorded collective sequence —
    the wire legs of ops_comm's quantize→collective→dequantize pairs.
    Lets a test/validator assert that an intended quantized program
    actually moves int8 on the interconnect (and vice versa)."""
    return [s for s in seq
            if len(s) > 3 and any(d == "int8" for d in s[3])]


def check_collective_order(fn, mesh, in_specs, out_specs, example_args):
    """Trace ``shard_map(fn)`` and validate its collective ordering.
    Returns the collective sequence [(prim, axes, shapes, dtypes), ...]
    on success; raises CollectiveOrderError on cond-branch divergence
    (including branches disagreeing on quantized-vs-f32 payloads)."""
    from jax import shard_map

    args = [
        a if isinstance(a, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(np.shape(a),
                                  getattr(a, "dtype", jnp.float32))
        for a in example_args
    ]
    f = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    closed = jax.make_jaxpr(f)(*args)
    seq = []
    _collect(closed, seq)
    return seq
