"""Parallelism: meshes, strategies, pipeline schedules, context parallel.

Replaces the reference's L5 (context.py DeviceGroups, communicator/,
comm-op graph rewriting, distributed_strategies/) with mesh + sharding
design (SURVEY.md §2.5 mapping table).
"""

from .mesh import (
    make_mesh, default_mesh, MeshAxes, local_device_count,
)
from . import pipeline
from .pipeline import (
    spmd_pipeline, spmd_pipeline_1f1b, stack_stage_params,
    shard_stacked_params,
    gpipe_schedule, one_f_one_b_schedule, PipelineStage, PipelineTrainer,
)
from . import context_parallel
from .context_parallel import (
    ring_attention, ulysses_attention, blockwise_attention,
)
from . import distributed_strategies
from .distributed_strategies import (
    DataParallel, ModelParallel4LM, ExpertParallel, PipelineParallel4LM,
    FSDP, BaseSearchingStrategy, ShardingPlan,
)
from . import preduce
from .preduce import PartialReduce
from . import collective_check
from .collective_check import check_collective_order, \
    CollectiveOrderError
