"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence parallelism (SURVEY.md §5.7 — verified
absent); this module is the new-capability requirement for long-context
training.  Two idiomatic TPU designs over a 'cp' mesh axis:

1. ``ring_attention`` — Q stays put, K/V blocks rotate around the ring via
   ``lax.ppermute`` while each device accumulates its attention output with
   online (streaming) softmax, so the full S x S score matrix never
   materializes and sequence length scales linearly with the number of
   devices.  Communication rides ICI neighbor links (ppermute), overlapping
   with the blockwise compute.  Causal masking is applied per (q-block,
   kv-block) pair from the ring offsets, skipping fully-masked blocks'
   contribution numerically (they contribute exp(-inf)=0).

2. ``ulysses_attention`` — all_to_all swaps sequence sharding for head
   sharding ([B, S/cp, H, D] -> [B, S, H/cp, D]), runs ordinary full
   attention per local head group, and swaps back.  Cheaper at moderate S
   (two all_to_alls vs cp ppermute rounds), requires cp | H.

Both are pure jax (differentiable; autodiff through scan/ppermute yields
the reverse ring) and compose with dp/tp axes of the same mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def _block_attn_update(q, k, v, bias, m, l, o):
    """One streaming-softmax accumulation step.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; bias: [Sq, Sk] additive
    (0 or NEG_INF); m, l: [B, H, Sq]; o: [B, Sq, H, D].
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = s + bias[None, None, :, :]
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard rows where everything so far is masked (m_new == NEG_INF)
    safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    alpha = jnp.exp(jnp.clip(m - m_new, max=0.0))
    alpha = jnp.where(m <= NEG_INF / 2, 0.0, alpha)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def _finalize(l, o):
    denom = jnp.where(l == 0.0, 1.0, l).transpose(0, 2, 1)[..., None]
    return o / denom


def _batch_axis(mesh, cp_axis, batch):
    """Shard the batch dim over 'dp' when the mesh has one and the batch
    divides it: entering the shard_map with the batch replicated forces
    GSPMD into a full rematerialization (unshard/reshard) around every
    call — the body does no cross-batch communication, so slicing it per
    dp device is free.  Indivisible batches (e.g. B=1 inference on a
    training mesh) stay replicated."""
    if "dp" in mesh.axis_names and cp_axis != "dp" \
            and batch % mesh.shape["dp"] == 0:
        return "dp"
    return None


def ring_attention(q, k, v, *, mesh, axis="cp", causal=False, impl=None,
                   block_q=512, block_k=1024):
    """Blockwise ring attention over sequence-sharded q/k/v.

    Args:
      q, k, v: [B, S, H, D] arrays; the S dim is (or will be) sharded over
        ``axis``.  Pass either global (replicated/sharded jax.Arrays under
        jit) — shard_map slices per device.  If the mesh has a 'dp' axis
        and B divides it, the batch dim is dp-sharded too (replicated
        otherwise).
      causal: apply a causal mask using global positions.
      impl: ``'flash'`` — each rotation's block runs the fused Pallas
        kernel (``flash_attention_with_carry``): the previous rotation's
        (o, lse) partial seeds the kernel's streaming state, so the
        cross-rotation merge happens in the kernel prologue with no
        separate pass; blocks wholly above the causal diagonal are
        SKIPPED (lax.switch), so causal costs ~half the FLOPs.
        ``'exact'`` — unfused streaming-softmax oracle.
        ``None`` — flash on TPU, exact elsewhere (the oracle doubles as
        the CPU-mesh test path; flash still runs there in interpret mode
        when requested explicitly).
      block_q, block_k: flash kernel block sizes (flash impl only).

    Returns [B, S, H, D] attention output, sequence-sharded like q.
    """
    if impl is None:
        impl = "flash" if jax.default_backend() == "tpu" else "exact"
    if impl not in ("flash", "exact"):
        raise ValueError(f"ring_attention impl must be 'flash', 'exact' "
                         f"or None (auto), got {impl!r}")
    if impl == "flash":
        return _ring_attention_flash(q, k, v, mesh=mesh, axis=axis,
                                     causal=causal, block_q=block_q,
                                     block_k=block_k)
    cp = mesh.shape[axis]
    S = q.shape[1]
    assert S % cp == 0, f"seq {S} not divisible by cp={cp}"
    blk = S // cp
    bax = _batch_axis(mesh, axis, q.shape[0])

    def per_device(q, k, v):
        # local blocks [B, blk, H, D]
        my = jax.lax.axis_index(axis)
        B, _, H, D = q.shape
        m = jnp.full((B, H, blk), NEG_INF, q.dtype)
        l = jnp.zeros((B, H, blk), q.dtype)
        o = jnp.zeros_like(q)  # varying already (derived from sharded q)
        # carry typing: m/l must vary over every manual axis q varies
        # over, or the scan carry changes type after the first update
        vary = (axis,) if bax is None else (axis, bax)
        m = jax.lax.pcast(m, vary, to="varying")
        l = jax.lax.pcast(l, vary, to="varying")
        shift = [(i, (i + 1) % cp) for i in range(cp)]
        q_pos = my * blk + jnp.arange(blk)

        def step(carry, t):
            k_t, v_t, m, l, o = carry
            # after t rotations we hold the kv block of device (my - t) % cp
            kv_owner = (my - t) % cp
            kv_pos = kv_owner * blk + jnp.arange(blk)
            if causal:
                bias = jnp.where(q_pos[:, None] >= kv_pos[None, :],
                                 0.0, NEG_INF).astype(q.dtype)
            else:
                bias = jnp.zeros((blk, blk), q.dtype)
            m, l, o = _block_attn_update(q, k_t, v_t, bias, m, l, o)
            k_n = jax.lax.ppermute(k_t, axis, shift)
            v_n = jax.lax.ppermute(v_t, axis, shift)
            return (k_n, v_n, m, l, o), None

        (k, v, m, l, o), _ = jax.lax.scan(
            step, (k, v, m, l, o), jnp.arange(cp))
        return _finalize(l, o)

    spec = P(bax, axis, None, None)
    return shard_map(per_device, mesh=mesh,
                     in_specs=(spec, spec, spec), out_specs=spec)(q, k, v)


def _ring_attention_flash(q, k, v, *, mesh, axis, causal, block_q,
                          block_k):
    """Flash-in-ring (VERDICT r2 item 6): every rotation's (q-block,
    kv-block) pair runs the fused Pallas kernel.  Per rotated block
    exactly one of three cases applies, dispatched at runtime on the
    ring offset (lax.switch):

      kv_owner  > mine (causal): fully masked -> skipped outright
      kv_owner == mine (causal): the diagonal -> flash(causal=True)
      otherwise:                 fully live   -> flash(causal=False)

    Block alignment makes the diagonal case plain local causal masking,
    so the kernel needs no offset plumbing.

    Two r4 perf changes (VERDICT r3 item 2):
    * the per-rotation (o, lse) merge is FUSED into the kernel prologue
      — ``flash_attention_with_carry`` seeds the kernel's streaming
      (m, l, acc) state from the previous rotation's partial, so no
      separate elementwise pass over the output runs per rotation;
    * the KV ppermute is issued BEFORE the block compute, so the
      latency-hiding scheduler can run the ICI rotation underneath the
      flash kernel (the next iteration, not this one, consumes it).

    Backward differentiates the chained kernel VJPs (the carry behaves
    as one virtual key row; see _flash_stats_carry_bwd_rule)."""
    from ..kernels.flash_attention import flash_attention_with_carry
    cp = mesh.shape[axis]
    S = q.shape[1]
    assert S % cp == 0, f"seq {S} not divisible by cp={cp}"
    bax = _batch_axis(mesh, axis, q.shape[0])

    def per_device(q, k, v):
        my = jax.lax.axis_index(axis)
        B, blk, H, D = q.shape
        shift = [(i, (i + 1) % cp) for i in range(cp)]
        o0 = jnp.zeros((B, blk, H, D), jnp.float32)
        lse0 = jnp.full((B, H, blk), NEG_INF, jnp.float32)

        def blk_full(k_t, v_t, o, lse):
            return flash_attention_with_carry(
                q, k_t, v_t, o, lse, causal=False,
                block_q=block_q, block_k=block_k)

        def blk_diag(k_t, v_t, o, lse):
            return flash_attention_with_carry(
                q, k_t, v_t, o, lse, causal=True,
                block_q=block_q, block_k=block_k)

        def blk_skip(k_t, v_t, o, lse):
            return o, lse

        def step(carry, t):
            k_t, v_t, o, lse = carry
            # rotation first: independent of the block compute, so the
            # scheduler can overlap the ppermute with the kernel
            k_n = jax.lax.ppermute(k_t, axis, shift)
            v_n = jax.lax.ppermute(v_t, axis, shift)
            kv_owner = (my - t) % cp
            if causal:
                case = jnp.where(kv_owner > my, 2,
                                 jnp.where(kv_owner == my, 1, 0))
            else:
                case = jnp.zeros((), jnp.int32)
            o, lse = jax.lax.switch(
                case, [blk_full, blk_diag, blk_skip], k_t, v_t, o, lse)
            return (k_n, v_n, o, lse), None

        (_, _, o, lse), _ = jax.lax.scan(
            step, (k, v, o0, lse0), jnp.arange(cp))
        return o.astype(q.dtype)

    spec = P(bax, axis, None, None)
    # check_vma off: pallas_call out_shapes carry no varying-axes info
    return shard_map(per_device, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, *, mesh, axis="cp", causal=False,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style: all_to_all seq<->head, full local attention.

    q, k, v: [B, S, H, D] with S sharded over ``axis``; requires cp | H.
    The batch dim additionally shards over a 'dp' mesh axis when B
    divides it.  ``attn_fn(q, k, v, causal)`` may override the local
    attention (e.g. the Pallas flash kernel); default is exact softmax
    attention.
    """
    cp = mesh.shape[axis]
    B, S, H, D = q.shape
    assert H % cp == 0, f"heads {H} not divisible by cp={cp}"

    if attn_fn is None:
        def attn_fn(q, k, v, causal):
            # lazy import: single source of the exact-attention math
            from ..kernels.flash_attention import mha_reference
            return mha_reference(q, k, v, causal=causal)

    def per_device(q, k, v):
        # [B, S/cp, H, D] -> gather seq, scatter heads -> [B, S, H/cp, D]
        def seq_to_head(x):
            x = jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                   tiled=True)
            return x

        def head_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        ql, kl, vl = seq_to_head(q), seq_to_head(k), seq_to_head(v)
        ol = attn_fn(ql, kl, vl, causal)
        return head_to_seq(ol)

    spec = P(_batch_axis(mesh, axis, q.shape[0]), axis, None, None)
    # check_vma off: attn_fn may be a pallas_call, whose out_shape carries
    # no varying-axes info under shard_map's vma tracking
    return shard_map(per_device, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_vma=False)(q, k, v)


def blockwise_attention(q, k, v, *, block_size=512, causal=False):
    """Single-device blockwise (memory-efficient) attention with the same
    streaming-softmax math as the ring — the cp=1 degenerate case and the
    numerics oracle for tests."""
    B, S, H, D = q.shape
    Sk = k.shape[1]
    blk = min(block_size, Sk)
    m = jnp.full((B, H, S), NEG_INF, q.dtype)
    l = jnp.zeros((B, H, S), q.dtype)
    o = jnp.zeros_like(q)
    q_pos = jnp.arange(S)
    # ragged final block handled by python slicing (shapes are static)
    for start in range(0, Sk, blk):
        kj = k[:, start:start + blk]
        vj = v[:, start:start + blk]
        kv_pos = start + jnp.arange(kj.shape[1])
        if causal:
            bias = jnp.where(q_pos[:, None] >= kv_pos[None, :],
                             0.0, NEG_INF).astype(q.dtype)
        else:
            bias = jnp.zeros((S, kj.shape[1]), q.dtype)
        m, l, o = _block_attn_update(q, kj, vj, bias, m, l, o)
    return _finalize(l, o)
