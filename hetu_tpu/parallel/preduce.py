"""Partial reduce — straggler-tolerant data parallelism (SIGMOD'21).

Reference: python/hetu/preduce.py:8-43 `PartialReduce`: a PS-side
matchmaker (`kPReduceGetPartner`, ps/psf/preduce.h, preduce_handler.cc)
returns a dynamic subgroup of currently-ready workers; the group then
allreduce-averages gradients over a cached per-group NCCL communicator.

TPU mapping (SURVEY.md §2.5): inside one synchronous SPMD program there
are no stragglers, so partial reduce matters at the *process* level
(multi-host / multi-process CPU workers).  This is the host-coordinated
variant: the same PS matchmaker forms the group and stamps it with a
server-assigned match sequence (the shared scratch-key namespace — a
local round counter would diverge when membership varies), and the
average rides the PS as an accumulate + pull.  Semantics match the
reference: the result is the mean over the matched subgroup only.
"""

from __future__ import annotations

import time

import numpy as np


class PartialReduce:
    """Reference API: get_partner() -> ranks; preduce(arr, partner) ->
    subgroup mean (preduce.py:8-43)."""

    def __init__(self, reduce_key=0, max_worker=-1, wait_time=1.0,
                 client=None):
        from ..ps.client import PSClient

        self.client = client or PSClient.get()
        self.reduce_key = reduce_key
        self.max_worker = max_worker if max_worker > 0 \
            else self.client.nrank
        self.wait_time = wait_time
        self._last_seq = 0

    def get_partner(self, sync=True):
        """Ask the matchmaker for the current ready subgroup (ranks,
        sorted).  `sync` kept for reference API parity (async variant
        returns immediately after registering)."""
        ranks, seq = self.client.preduce_get_partner(
            self.reduce_key, self.max_worker, self.wait_time)
        self._last_seq = seq
        return tuple(sorted(ranks))

    def preduce(self, array, partner=None, timeout=30.0):
        """Average `array` over the matched subgroup via the PS."""
        if partner is None:
            partner = self.get_partner()
        if len(partner) <= 1:
            return np.asarray(array, np.float32)
        arr = np.asarray(array, np.float32)
        group_id = "_".join(map(str, partner))
        key = f"__preduce_{self.reduce_key}_{group_id}_{self._last_seq}"
        count_key = key + "_n"
        self.client.parameter_init(key, arr.shape, init_type="constant",
                                   arg1=0.0)
        self.client.parameter_init(count_key, (1,), init_type="constant",
                                   arg1=0.0)
        # raw accumulate (no server optimizer on the scratch keys); the
        # data push strictly precedes the count bump, so count==len means
        # all contributions have landed
        self.client.push(key, arr)
        self.client.push(count_key, np.ones(1, np.float32))
        deadline = time.time() + timeout
        try:
            while time.time() < deadline:
                try:
                    n = float(np.asarray(self.client.pull(count_key))[0])
                except (KeyError, RuntimeError):
                    # a faster member timed out and cleared the scratch
                    # keys: this round is abandoned for everyone
                    raise TimeoutError(
                        "preduce: round abandoned (scratch keys cleared "
                        "by a timed-out member)")
                if n >= len(partner):
                    break
                time.sleep(0.005)
            else:
                raise TimeoutError("preduce: group members missing")
            try:
                total = np.asarray(self.client.pull(key))
            except (KeyError, RuntimeError):
                raise TimeoutError(
                    "preduce: round abandoned (scratch keys cleared by a "
                    "timed-out member)")
        except TimeoutError:
            # best-effort cleanup so incomplete rounds don't leak arrays
            # on the PS (other members hitting the same timeout race to
            # the same clears; param_clear is idempotent)
            self.client.clear(key)
            self.client.clear(count_key)
            raise
        # second count bump marks "read done"; the lowest rank clears the
        # scratch keys once everyone has read (best-effort, bounded wait)
        self.client.push(count_key, np.ones(1, np.float32))
        if min(partner) == self.client.rank:
            while time.time() < deadline:
                try:
                    n = float(np.asarray(self.client.pull(count_key))[0])
                except (KeyError, RuntimeError):
                    # a slower member timed out and already cleared the
                    # scratch keys; our mean is in hand — nothing to do
                    break
                if n >= 2 * len(partner):
                    self.client.clear(key)
                    self.client.clear(count_key)
                    break
                time.sleep(0.005)
        return total / len(partner)
