"""Pipeline graph partitioner: split a built forward graph into stages.

TPU-native counterpart of the reference's recv/send-boundary partitioning
(pipeline_subexecutor.py:29-81 splits the op list at PipelineReceive/Send
nodes placed by per-op DeviceGroup contexts; gpipe_subexecutor.py:33-111
drives the partitions).  Here there are no per-op device contexts: the
partitioner discovers stage boundaries structurally.

Two-level algorithm:

1. **Cut points.**  Walk the topo order of the loss graph tracking the set
   of live compute values (produced before, consumed after).  A position
   where exactly ONE value is live is a legal pipeline cut: one activation
   crosses the boundary (the same single-tensor-boundary invariant the
   reference's PipelineSend/Receive pairs enforce).

2. **Uniform body detection.**  Blocks between consecutive cuts are
   fingerprinted (op types + op attrs + param shapes/trainability, in topo
   order).  The longest run of identical, *closed* blocks (no reads of
   another block's placeholders, no feed inputs) is the pipeline body —
   for a transformer, the N identical layers.  Everything before is `pre`
   (embedding), everything after is `post` (head + loss): they run outside
   the pipeline loop, vectorized over microbatches — the non-uniform-stage
   story the scan pipeline itself cannot express.

The executor lowers a plan with a uniform body onto ``spmd_pipeline``
(stage-stacked params over the 'pp' mesh axis); graphs without one fall
back to the trajectory-equivalent microbatch-scan path (see
pipeline_executor.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..graph.autodiff import find_topo_sort
from ..graph.node import Op
from ..graph.ops_misc import PlaceholderOp


# attributes that never affect a node's math
_SKIP_ATTRS = frozenset({
    "inputs", "name", "id", "raw_ctx", "dtype",
})


def _simple(v):
    return isinstance(v, (int, float, bool, str, type(None)))


def _array_digest(v):
    """Content digest for array-valued statics (closure constants like
    lookup tables or assignment masks).  Without this, two layers that
    differ only in a constant array would fingerprint equal and the
    template-stacked SPMD body would silently use layer 0's constant
    everywhere."""
    import numpy as _np
    a = _np.asarray(v)
    return ("array", a.shape, str(a.dtype),
            int(_np.int64(abs(hash(a.tobytes())))))


def _callable_fingerprint(f):
    """SimpleOp wraps a closure: its identity (which factory built it) and
    the closed-over statics (slice indices, reshape targets, axes) are the
    op's math.  Without this, Slice and Reshape nodes are
    indistinguishable and the template-stacking would silently apply the
    wrong op."""
    items = [getattr(f, "__qualname__", repr(f))]
    for cell in (getattr(f, "__closure__", None) or ()):
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if _simple(v):
            items.append(v)
        elif isinstance(v, Op):
            items.append(("op", type(v).__name__))
        elif isinstance(v, (tuple, list)) and all(_simple(e) for e in v):
            items.append(tuple(v))
        elif hasattr(v, "shape") and hasattr(v, "dtype"):
            items.append(_array_digest(v))
        elif callable(v):
            items.append(getattr(v, "__qualname__", "fn"))
        else:
            # unknown static: include its type so at least differently-
            # typed closures never collide
            items.append(("opaque", type(v).__name__))
    return tuple(items)


def _attr_fingerprint(node):
    """Hashable digest of a node's math-relevant static attributes."""
    items = []
    for k in sorted(vars(node)):
        if k in _SKIP_ATTRS:
            continue
        v = vars(node)[k]
        if _simple(v):
            items.append((k, v))
        elif isinstance(v, Op):
            items.append((k, ("op", type(v).__name__)))
        elif isinstance(v, (tuple, list)):
            if all(_simple(e) for e in v):
                items.append((k, tuple(v)))
            else:
                items.append((k, len(v)))
        elif hasattr(v, "shape") and hasattr(v, "dtype"):
            items.append((k, _array_digest(v)))
        elif callable(v):
            items.append((k, _callable_fingerprint(v)))
    return tuple(items)


@dataclass
class Block:
    """A contiguous topo slice between two cuts."""
    nodes: list                    # topo slice (placeholders included)
    boundary_out: object           # the single live node at the exit cut
    params: list = field(default_factory=list)    # variable placeholders
    feeds: list = field(default_factory=list)     # non-variable placeholders
    closed: bool = True            # no external non-boundary inputs

    def signature(self):
        sig = []
        for n in self.nodes:
            if isinstance(n, PlaceholderOp):
                sig.append(("var" if n.is_variable else "feed",
                            tuple(n.shape) if n.shape else None,
                            getattr(n, "trainable", False)))
            else:
                sig.append((type(n).__name__, _attr_fingerprint(n)))
        return tuple(sig)


@dataclass
class PipelinePlan:
    """Partition of a loss graph for pipelining.

    ``body_blocks`` is non-empty iff a uniform body was found:
    R = len(body_blocks) identical blocks, groupable into S stages of
    R/S blocks each.  ``pre_nodes``/``post_nodes`` run outside the loop.
    """
    loss: object
    blocks: list                       # every block, in order
    pre_nodes: list
    body_blocks: list                  # uniform run (possibly empty)
    post_nodes: list
    body_entry: object                 # node whose value enters block 0
    # per-block param placeholders, positionally aligned across blocks
    body_params: list
    pre_params: list
    post_params: list
    pre_feeds: list
    post_feeds: list

    @property
    def uniform(self):
        return len(self.body_blocks) > 0

    def num_body_blocks(self):
        return len(self.body_blocks)


def find_cuts(topo):
    """Positions i where exactly one compute value is live after topo[i].

    Returns [(i, boundary_node)], deduped to the EARLIEST position per
    boundary, so trailing placeholders (the next layer's weights in DFS
    order) land in the block that consumes them."""
    pos = {id(n): i for i, n in enumerate(topo)}
    last_use = {}
    for n in topo:
        for inp in n.inputs:
            last_use[id(inp)] = max(last_use.get(id(inp), -1), pos[id(n)])
    live = {}          # id -> node, compute values only
    cuts = []
    for i, n in enumerate(topo):
        for inp in n.inputs:
            if last_use.get(id(inp), -1) == i:
                live.pop(id(inp), None)
        if not isinstance(n, PlaceholderOp) and last_use.get(id(n), -1) > i:
            live[id(n)] = n
        if len(live) == 1 and i < len(topo) - 1:
            (b,) = live.values()
            if not (cuts and cuts[-1][1] is b):
                cuts.append((i, b))
    return cuts


def _make_blocks(topo, cuts):
    # Cross-block references to compute values other than the incoming
    # boundary are impossible (they would make the intervening cuts have
    # two live values), so `closed` only tracks references to OTHER
    # blocks' placeholders (shared weights / global feeds) — those break
    # positional param stacking.
    blocks = []
    start = 0
    bounds = cuts + [(len(topo) - 1, topo[-1])]
    for (end, boundary) in bounds:
        nodes = topo[start:end + 1]
        blk = Block(nodes=nodes, boundary_out=boundary)
        inner = {id(n) for n in nodes}
        for n in nodes:
            if isinstance(n, PlaceholderOp):
                (blk.params if n.is_variable else blk.feeds).append(n)
            else:
                for inp in n.inputs:
                    if isinstance(inp, PlaceholderOp) and \
                            id(inp) not in inner:
                        blk.closed = False
        blocks.append(blk)
        start = end + 1
    return blocks


def _merge_blocks(blocks):
    out = Block(nodes=[n for b in blocks for n in b.nodes],
                boundary_out=blocks[-1].boundary_out)
    for b in blocks:
        out.params.extend(b.params)
        out.feeds.extend(b.feeds)
        out.closed = out.closed and b.closed
    return out


def _find_periodic_body(blocks, min_units):
    """Longest periodic run of blocks: sig[j] == sig[j - p] over a
    stretch, every block closed and feed-free.  A period p > 1 is a layer
    that the cut detector split into several blocks (e.g. a transformer
    layer = attn-residual / LN / FFN-residual / LN).  Returns
    (start_block, units, period) for the best (max block coverage, then
    smallest period) run with >= min_units complete periods."""
    sigs = [b.signature() for b in blocks]
    ok = [b.closed and not b.feeds for b in blocks]
    n = len(blocks)
    best = None        # (coverage, -p, start, units, p)
    # block 0 can never be in the body (no entry boundary)
    for p in range(1, (n - 1) // max(min_units, 2) + 1):
        for i in range(1, n - p + 1):
            if not all(ok[i:i + p]):
                continue
            e = i + p
            while e < n and ok[e] and sigs[e] == sigs[e - p]:
                e += 1
            units = (e - i) // p
            if units >= min_units:
                cand = (units * p, -p, i, units, p)
                if best is None or cand > best:
                    best = cand
    if best is None:
        return None
    return best[2], best[3], best[4]


def partition(loss, num_stages):
    """Build a PipelinePlan for ``loss`` targeting ``num_stages`` stages.

    Always succeeds; ``plan.uniform`` says whether the SPMD scan-pipeline
    lowering is available (R body blocks, R >= num_stages, R % S == 0
    after trimming extra leading blocks into ``pre``)."""
    topo = find_topo_sort([loss])
    cuts = find_cuts(topo)
    blocks = _make_blocks(topo, cuts)

    body = dict(body_blocks=[], body_entry=None, body_params=[])
    run = _find_periodic_body(blocks, max(num_stages, 2)) \
        if num_stages > 1 else None
    if run is not None:
        start, units, p = run
        usable = (units // num_stages) * num_stages
        start += (units - usable) * p      # trim extra units into pre
        merged = [_merge_blocks(blocks[start + u * p:start + (u + 1) * p])
                  for u in range(usable)]
        # template-based stage fn binds params positionally: alignment is
        # guaranteed by the shared periodic signature (param shapes in
        # topo order within each unit)
        body = dict(
            body_blocks=merged,
            body_entry=blocks[start - 1].boundary_out,
            body_params=[b.params for b in merged],
        )
        pre_blocks = blocks[:start]
        post_blocks = blocks[start + usable * p:]
    else:
        pre_blocks, post_blocks = blocks, []

    pre_nodes = [n for b in pre_blocks for n in b.nodes]
    post_nodes = [n for b in post_blocks for n in b.nodes]

    def vars_of(nodes):
        return [n for n in nodes
                if isinstance(n, PlaceholderOp) and n.is_variable]

    def feeds_of(nodes):
        return [n for n in nodes
                if isinstance(n, PlaceholderOp) and not n.is_variable]

    return PipelinePlan(
        loss=loss, blocks=blocks,
        pre_nodes=pre_nodes, post_nodes=post_nodes,
        pre_params=vars_of(pre_nodes), post_params=vars_of(post_nodes),
        pre_feeds=feeds_of(pre_nodes), post_feeds=feeds_of(post_nodes),
        **body)
