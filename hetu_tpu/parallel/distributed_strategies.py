"""Distribution strategies: assign meshes + sharding specs to a graph.

Reference: python/hetu/distributed_strategies/ (DataParallel at simple.py:6,
plus ModelParallel4LM / PipelineParallel4LM / ExpertParallel stubs in the
fork).  The reference strategy assigns DeviceGroups per op; here a strategy
configures the Executor with a Mesh and per-variable PartitionSpecs — XLA
derives every collective from those.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from .mesh import make_mesh


class Strategy:
    def configure(self, executor):
        raise NotImplementedError


class DataParallel(Strategy):
    """Batch sharded over 'dp'; params replicated; XLA psums grads.
    Reference: distributed_strategies/simple.py:6-39 + OptimizerOp
    backward_hook AllReduce splicing (optimizer.py:154-159) — both collapse
    into sharding annotations here.

    ``aggregate``: None/'allreduce' keeps the plain XLA psum;
    'quant_allreduce' (or 'int8'/'quant', default taken from
    ``$HETU_COMM_QUANT``) splices the quantize→all_gather→dequantize
    comm-op trio (``graph/ops_comm.quantized_allreduce_op``) onto every
    DENSE gradient entering each optimizer — the reference OptimizerOp
    backward_hook splice, quantized.  Sparse (IndexedSlices) adjoints
    keep their structural path.  The pair is statically verified by
    ``analysis/shard_check.check_quantized_collectives`` before any
    compile; 'ps'/'hybrid' remain parity args for the PS comm modes."""

    _QUANT_MODES = ("quant", "int8", "quant_allreduce")

    def __init__(self, aggregate=None, num_devices=None):
        self.aggregate = aggregate  # parity arg ('allreduce'/'ps'/'hybrid')
        self.num_devices = num_devices

    def _quantized(self):
        if self.aggregate is not None:
            return str(self.aggregate).lower() in self._QUANT_MODES
        from .. import quant
        return quant.comm_quant() == "int8"

    def configure(self, executor):
        if executor.config.mesh is None:
            n = self.num_devices or jax.device_count()
            executor.config.mesh = make_mesh({"dp": n})
        # params replicated (default spec None -> P())
        if self._quantized():
            self._splice_quantized_aggregation(executor)

    @staticmethod
    def _splice_quantized_aggregation(executor, axis="dp"):
        """Rewire every OptimizerOp's dense grads through the quantized
        comm-op pair.  Runs at configure time, BEFORE the subexecutors
        topo-sort, so the trio lands in every trace and in the static
        checkers' view of the graph."""
        from ..graph.ops_comm import quantized_allreduce_op
        from ..optimizer import OptimizerOp
        done = set()
        for nodes in executor.eval_node_dict.values():
            for n in nodes:
                if not isinstance(n, OptimizerOp) or id(n) in done:
                    continue
                done.add(id(n))
                for i, g in enumerate(n.inputs):
                    if i in n.sparse_inputs:
                        continue      # sparse adjoints stay structural
                    var = n.var_list[i]
                    n.inputs[i] = quantized_allreduce_op(
                        g, axis=axis, shape=var.shape)


class ShardingPlan(Strategy):
    """Explicit per-variable PartitionSpecs — the unambiguous spec API.

    ``specs``: {var_name: PartitionSpec}; unlisted vars replicate.
    ``mesh_axes``: {'dp': 2, 'tp': 2} built into a Mesh when the executor
    has none.  Unknown var names raise (catches typos that name-pattern
    matching would silently ignore)."""

    def __init__(self, specs, mesh_axes=None, strict=True):
        self.specs = dict(specs)
        self.mesh_axes = mesh_axes
        self.strict = strict

    def configure(self, executor):
        if executor.config.mesh is None and self.mesh_axes:
            executor.config.mesh = make_mesh(self.mesh_axes)
        if executor.config.mesh is None:
            raise ValueError(
                "ShardingPlan needs a mesh: pass mesh= to the Executor or "
                "mesh_axes= to the plan (specs alone would silently run "
                "replicated)")
        unknown = set(self.specs) - set(executor.variables)
        if unknown and self.strict:
            raise KeyError(f"ShardingPlan names unknown variables: "
                           f"{sorted(unknown)}; known: "
                           f"{sorted(executor.variables)[:20]}...")
        for name, spec in self.specs.items():
            if name in executor.variables:
                executor.variables[name].sharding_spec = spec


class ModelParallel4LM(Strategy):
    """Megatron-style tensor parallel over 'tp': column-split attention/MLP
    in-projections, row-split out-projections.

    Preferred: pass ``specs`` ({var_name: PartitionSpec}) for explicit,
    typo-checked assignment.  Fallback: name-pattern matching (reference
    parity; patterns over variable names)."""

    def __init__(self, tp=None, dp=1, col_patterns=("qkv", "wi", "fc1", "expand"),
                 row_patterns=("proj", "wo", "fc2", "reduce"), specs=None):
        self.tp = tp
        self.dp = dp
        self.col_patterns = col_patterns
        self.row_patterns = row_patterns
        self.specs = specs

    def configure(self, executor):
        if executor.config.mesh is None:
            tp = self.tp or (jax.device_count() // self.dp)
            executor.config.mesh = make_mesh({"dp": self.dp, "tp": tp})
        if self.specs is not None:
            ShardingPlan(self.specs).configure(executor)
            return
        for name, node in executor.variables.items():
            if node.sharding_spec is not None:
                continue
            lname = name.lower()
            if any(p in lname for p in self.col_patterns) and len(node.shape or ()) == 2:
                node.sharding_spec = P(None, "tp")
            elif any(p in lname for p in self.row_patterns) and len(node.shape or ()) == 2:
                node.sharding_spec = P("tp", None)


class ExpertParallel(Strategy):
    """Experts sharded over 'ep' (reference: expert params excluded from
    allreduce by name match 'expert', optimizer.py:150-153; A2A over the
    expert axis).  Variables named '*expert*' with a leading expert dim get
    P('ep', ...)."""

    def __init__(self, ep=None, dp=1):
        self.ep = ep
        self.dp = dp

    def configure(self, executor):
        if executor.config.mesh is None:
            ep = self.ep or jax.device_count() // self.dp
            executor.config.mesh = make_mesh({"dp": self.dp, "ep": ep})
        for name, node in executor.variables.items():
            if "expert" in name.lower() and node.shape:
                spec = ["ep"] + [None] * (len(node.shape) - 1)
                node.sharding_spec = P(*spec)


class PipelineParallel4LM(Strategy):
    """Stage assignment hint holder; the scan-based pipeline executor in
    parallel/pipeline.py consumes it."""

    def __init__(self, pp=None, num_microbatches=None):
        self.pp = pp
        self.num_microbatches = num_microbatches

    def configure(self, executor):
        if executor.config.mesh is None:
            pp = self.pp or jax.device_count()
            executor.config.mesh = make_mesh({"pp": pp})
        executor.config.pipeline = executor.config.pipeline or "gpipe"
        if self.num_microbatches:
            executor.config.num_microbatches = self.num_microbatches


class FSDP(Strategy):
    """ZeRO-3-style parameter sharding over the 'dp' axis (SURVEY.md §2.5:
    absent in the reference core, a strategy dimension only in Galvatron's
    search space — first-class here because pjit makes it nearly free).

    Each variable's largest divisible dim is sharded over 'dp'; XLA
    all-gathers params into fwd/bwd and reduce-scatters gradients.
    Variables smaller than `min_size` replicate (gather overhead beats the
    memory win)."""

    def __init__(self, dp=None, min_size=1024):
        self.dp = dp
        self.min_size = min_size

    def configure(self, executor):
        if executor.config.mesh is None:
            dp = self.dp or jax.device_count()
            executor.config.mesh = make_mesh({"dp": dp})
        dp = executor.config.mesh.shape.get("dp", 1)
        if dp <= 1:
            return  # pre-existing mesh without a usable 'dp' axis
        for name, node in executor.variables.items():
            if node.sharding_spec is not None or not node.shape:
                continue
            if int(np.prod(node.shape)) < self.min_size:
                continue
            dims = len(node.shape)
            free = [d for d in range(dims) if node.shape[d] % dp == 0]
            if not free:
                continue
            d = max(free, key=lambda d: node.shape[d])
            spec = [None] * dims
            spec[d] = "dp"
            node.sharding_spec = P(*spec)


class BaseSearchingStrategy(Strategy):
    """Base for cost-model-driven strategies (Galvatron-equivalent planner
    in hetu_tpu.planner builds on this)."""

    def __init__(self, **kwargs):
        self.settings = kwargs
