"""Pipeline parallelism: SPMD scan pipeline + GPipe/1F1B/HetPipe schedules.

Replaces the reference's three pipeline subexecutors
(gpipe_subexecutor.py:7-123, pipedream_subexecutor.py:51-372, plus the
'hetpipe' mode at pipedream_subexecutor.py:317-328) and its P2P machinery
(PipelineSend.py/PipelineReceive.py wrapped in NCCL group calls,
executor.py:1010-1018; runtime shape handshake executor.py:779-838).

TPU-native design (SURVEY.md §2.5 "Pipeline parallel" rows):

1. ``spmd_pipeline`` — the production path.  Stages live on a 'pp' mesh
   axis; one jitted program runs a ``lax.scan`` over M + S - 1 ticks in
   which every device applies its stage and rotates activations to its
   successor with ``lax.ppermute``.  Differentiating through the scan
   yields the reverse pipeline automatically, so forward+backward+update
   is ONE XLA program — no per-microbatch Python choreography, no shape
   handshake (shapes are static), no group-call deadlock avoidance
   (ppermute is deadlock-free by construction).

2. ``GPipeSchedule`` / ``OneFOneBSchedule`` — explicit schedule
   generators with the same (microbatch, fwd|bwd) orderings the reference
   emits (gpipe: all-forward-then-all-backward, gpipe_subexecutor.py:33-111;
   1F1B generator pipedream_subexecutor.py:25-48).  Consumed by
   ``PipelineTrainer``, a host-loop driver over per-stage jitted functions
   that reproduces the reference semantics exactly — including PipeDream
   weight stashing (copy_latest_weight, pipedream_subexecutor.py:130-147)
   and HetPipe local-update-then-sync (grad_accum_map, :149-170, 317-328)
   — and doubles as the semantics oracle for tests.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map


# --------------------------------------------------------------------------- #
# 1. SPMD scan pipeline (the TPU-native path)
# --------------------------------------------------------------------------- #

def spmd_pipeline(stage_fn, stage_params, microbatches, *, mesh,
                  axis="pp", checkpoint_stages=True, mb_spec=None,
                  stage_takes_index=False, manual_axes=None):
    """Run ``microbatches`` through a pipeline of S stages over mesh axis
    ``axis`` in one SPMD program.

    Args:
      stage_fn: ``(params_for_one_stage, x) -> y`` applied by every stage
        (uniform-stage pipelining; put embedding/head outside or fold them
        into first/last stage params with dead weights elsewhere).
      stage_params: pytree whose leaves have leading dim S (stage-stacked),
        sharded ``P(axis)`` on the leading dim.
      microbatches: array ``[M, mb, ...]`` — M microbatches, replicated
        along ``axis``.
      mesh: the device mesh containing ``axis``.
      checkpoint_stages: rematerialize each stage application in the
        backward pass (the usual memory/flops trade on TPU).
      mb_spec: PartitionSpec for the microbatch array (default fully
        replicated).  Pass e.g. ``P(None, 'dp')`` on a (pp, dp) mesh to
        run one pipeline per data-parallel replica.
      stage_takes_index: call ``stage_fn(params, x, m)`` with the
        MICROBATCH index m (= tick - stage, clipped to [0, M)) — lets
        callers decorrelate per-microbatch state (e.g. dropout RNG).
        Keyed by m rather than the raw tick so that a recompute of the
        same microbatch under a different schedule (the 1F1B backward)
        reproduces the exact same randomness.
      manual_axes: axes the shard_map is MANUAL over (default: all).
        Passing {'pp'} leaves the other mesh axes to GSPMD, so tensor-
        parallel shardings on the stage params partition the in-stage
        matmuls automatically (composed pp x tp x dp) — in/out specs then
        mention only the manual axes.

    Returns ``[M, mb, ...]`` outputs of the last stage, replicated.

    The schedule: tick t, device d computes microbatch ``t - d`` (when in
    range); total ticks T = M + S - 1; bubble fraction (S-1)/T, identical
    to GPipe.  Backward through the scan gives the reversed schedule, so
    memory behavior matches GPipe (O(M + S) live boundary activations)
    unless ``checkpoint_stages`` trades stage INTERNALS for recompute.
    For the O(S) activation high-water schedule use
    ``spmd_pipeline_1f1b``.
    """
    S = mesh.shape[axis]
    M = microbatches.shape[0]
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn

    def per_device(params, mb):
        # params: leaves [1, ...] (this device's stage); mb: [M, mb, ...]
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)
        T = M + S - 1
        # carries become device-varying after the first tick; mark them so
        state = jax.lax.pcast(jnp.zeros_like(mb[0]), (axis,), to="varying")
        outputs = jax.lax.pcast(jnp.zeros_like(mb), (axis,), to="varying")
        shift = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (clamped; masked-out later)
            inp = jax.lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, M - 1), keepdims=False)
            x = jnp.where(stage == 0, inp, state)
            m_idx = jnp.clip(t - stage, 0, M - 1)
            y = fn(params, x, m_idx) if stage_takes_index else fn(params, x)
            # last stage emits microbatch t - (S-1); masked unconditional
            # write (lax.cond is off the table: branches would differ in
            # device-varyingness under shard_map's vma tracking)
            out_idx = t - (S - 1)
            safe = jnp.clip(out_idx, 0, M - 1)
            valid = jnp.logical_and(stage == S - 1,
                                    jnp.logical_and(out_idx >= 0, out_idx < M))
            old = jax.lax.dynamic_index_in_dim(outputs, safe, keepdims=False)
            upd = jnp.where(valid, y, old)
            outputs = jax.lax.dynamic_update_index_in_dim(outputs, upd, safe, 0)
            state = jax.lax.ppermute(y, axis, shift)
            return (state, outputs), None

        (_, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(T))
        # broadcast last stage's buffer to every device (differentiable)
        outputs = jax.lax.psum(
            jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), axis)
        return outputs

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    rep = mb_spec if mb_spec is not None \
        else P(*([None] * microbatches.ndim))
    kw = {}
    if manual_axes is not None:
        kw["axis_names"] = frozenset(manual_axes)
    return shard_map(
        per_device, mesh=mesh,
        in_specs=(pspec, rep), out_specs=rep, **kw,
    )(stage_params, microbatches)


def spmd_pipeline_1f1b(stage_fn, stage_params, microbatches, *, mesh,
                       axis="pp", mb_spec=None, manual_axes=None):
    """1F1B pipeline: same contract as ``spmd_pipeline`` with
    ``stage_takes_index=True``, but the backward pass runs a genuine
    staggered one-forward-one-backward schedule whose activation
    high-water is **O(S) in-flight microbatches per device** instead of
    the O(M + S) saved scan carries that differentiating a forward-only
    scan produces.

    Reference counterpart: the generator 1F1B scheduler + bounded
    in-flight buffer recycling of pipedream_subexecutor.py:25-48,213-221
    (driven per-op from the host there; here the whole staggered schedule
    is one XLA program).

    Mechanics (custom VJP, two phases):

    * primal/forward: the plain forward pipeline scan (nothing saved for
      AD).  The microbatch-input residual is saved RESHARDED over the
      pipeline axis — ``[S, M/S, ...]`` with spec ``P(axis)`` — so each
      device retains only M/S boundary inputs, not the full replicated
      [M, ...] (which would itself be the O(M) cost 1F1B exists to
      avoid).  Device 0 fetches its per-tick ingest slot from the owner
      via a masked psum; the cotangents dys / d(xs) move the same way.
    * backward: one combined scan of T = M + 2S - 1 ticks.  Each tick a
      device (1) re-forwards microbatch ``f = t - d`` and passes the
      boundary activation to its successor — storing the stage INPUT in a
      circular buffer of ``K = min(M, 2S-1)`` slots — and (2) runs the
      VJP of microbatch ``b = t - (2S-1-d)`` from the buffered input,
      consuming the cotangent rotated back from its successor and
      accumulating its stage's param grads.  Stage internals are
      rematerialized inside the per-tick VJP, so per-device live
      activation state is K boundary slots + M/S input residuals + one
      stage's internals — vs the M+S-1 saved carries of differentiating
      the forward scan (buffer recycling = the slot reuse of
      pipedream_subexecutor.py:213-221).

    Per-microbatch-per-stage cost is one extra forward vs the
    remat-gpipe lowering (re-forward for the rotation + VJP recompute),
    plus three boundary-sized psums per tick for the sharded-residual
    traffic — the price of the O(S) buffer with boundary-only storage.

    When M is not a multiple of S the residual stays replicated (the
    schedule is unchanged; only the memory bound loosens to M + 2S).

    The math is IDENTICAL to gpipe (grads summed over all microbatches,
    one update), so trajectories match to summation-order noise.
    """
    S = mesh.shape[axis]
    M = microbatches.shape[0]
    K = min(M, 2 * S - 1)
    msh = M // S if (M % S == 0 and S > 1) else None   # per-device slots

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    rep = mb_spec if mb_spec is not None \
        else P(*([None] * microbatches.ndim))
    shard_res = P(axis, *rep)        # [S, M/S, ...] over the pipe axis
    kw = {}
    if manual_axes is not None:
        kw["axis_names"] = frozenset(manual_axes)

    def fwd_only(params, mb):
        return spmd_pipeline(stage_fn, params, mb, mesh=mesh, axis=axis,
                             checkpoint_stages=False, mb_spec=mb_spec,
                             stage_takes_index=True,
                             manual_axes=manual_axes)

    def reshard(arr):
        """[M, ...] -> [S, M/S, ...] placed one block per pipe device."""
        return jax.lax.with_sharding_constraint(
            arr.reshape((S, msh) + arr.shape[1:]),
            NamedSharding(mesh, shard_res))

    @jax.custom_vjp
    def pipe(params, mb):
        return fwd_only(params, mb)

    def pipe_fwd(params, mb):
        ys = fwd_only(params, mb)
        return ys, (params, reshard(mb) if msh else mb)

    def pipe_bwd(res, dys):
        params, res_mb = res

        def per_device(params, mb, dys):
            # sharded layout: mb/dys leaves [1, M/S, ...]; replicated
            # fallback: [M, ...]
            params = jax.tree_util.tree_map(lambda p: p[0], params)
            if msh:
                mb, dys = mb[0], dys[0]
            d = jax.lax.axis_index(axis)
            T = M + 2 * S - 1
            fwd_perm = [(i, (i + 1) % S) for i in range(S)]
            bwd_perm = [(i, (i - 1) % S) for i in range(S)]

            def fetch(shard, m):
                """Value for global microbatch m out of the pp-sharded
                [M/S, ...] block (masked psum from the owner); replicated
                fallback reads directly.  ``m`` must be UNIFORM across
                the pipe axis — a device-varying index would make each
                device contribute a different row and the psum would mix
                microbatches."""
                if not msh:
                    return jax.lax.dynamic_index_in_dim(shard, m,
                                                        keepdims=False)
                v = jax.lax.dynamic_index_in_dim(shard, m % msh,
                                                 keepdims=False)
                v = jnp.where(d == m // msh, v, jnp.zeros_like(v))
                return jax.lax.psum(v, axis)

            zero_x = jnp.zeros(mb.shape[1:], mb.dtype)
            carry0 = (
                zero_x,                                    # fwd rotation
                jnp.zeros(dys.shape[1:], dys.dtype),       # bwd rotation
                jnp.zeros((K,) + mb.shape[1:], mb.dtype),  # K-slot buffer
                jax.tree_util.tree_map(jnp.zeros_like, params),
                jnp.zeros_like(mb),                        # d(xs) shard
            )

            def tick(carry, t):
                y_in, dx_in, buf, dpar, dxs = carry

                # ---- backward slot: microbatch b = t - (2S-1-d).
                # Read the residual BEFORE the forward slot writes: when
                # K slots wrap, read and write hit the same slot on the
                # same tick.
                b = t - (2 * S - 1 - d)
                b_act = jnp.logical_and(b >= 0, b < M)
                b_safe = jnp.clip(b, 0, M - 1)
                x_res = jax.lax.dynamic_index_in_dim(buf, b_safe % K,
                                                     keepdims=False)
                # device S-1's backward microbatch is t - S: a UNIFORM
                # index (fetch requires one; b_safe is device-varying)
                g_top = fetch(dys, jnp.clip(t - S, 0, M - 1))
                g_in = jnp.where(d == S - 1, g_top, dx_in)
                g_in = jnp.where(b_act, g_in, jnp.zeros_like(g_in))
                _, vjp = jax.vjp(
                    lambda p, xx: stage_fn(p, xx, b_safe), params, x_res)
                dp, dx = vjp(g_in)
                dpar = jax.tree_util.tree_map(
                    lambda a, g: a + jnp.where(b_act, g, 0).astype(
                        a.dtype), dpar, dp)
                # deliver device 0's d(input) — its backward microbatch
                # is the uniform index t - (2S-1) — to the shard owner
                m0 = t - (2 * S - 1)
                m0_act = jnp.logical_and(m0 >= 0, m0 < M)
                m0_safe = jnp.clip(m0, 0, M - 1)
                if msh:
                    dxb = jax.lax.psum(
                        jnp.where(d == 0, dx, jnp.zeros_like(dx)), axis)
                    slot = m0_safe % msh
                    keep = jnp.logical_and(m0_act, d == m0_safe // msh)
                else:
                    dxb = dx
                    slot = m0_safe
                    keep = jnp.logical_and(m0_act, d == 0)
                old_dx = jax.lax.dynamic_index_in_dim(dxs, slot,
                                                      keepdims=False)
                dxs = jax.lax.dynamic_update_index_in_dim(
                    dxs, jnp.where(keep, dxb, old_dx), slot, 0)

                # ---- forward slot: microbatch f = t - d (same flow as
                # the forward pipeline; here it feeds the residual buffer
                # and the successor's next tick)
                f = t - d
                f_act = jnp.logical_and(f >= 0, f < M)
                f_safe = jnp.clip(f, 0, M - 1)
                # device 0 ingests microbatch t: a uniform fetch index
                x_f = jnp.where(d == 0, fetch(mb, jnp.clip(t, 0, M - 1)),
                                y_in)
                y = stage_fn(params, x_f, f_safe)
                old_slot = jax.lax.dynamic_index_in_dim(buf, f_safe % K,
                                                        keepdims=False)
                buf = jax.lax.dynamic_update_index_in_dim(
                    buf, jnp.where(f_act, x_f, old_slot), f_safe % K, 0)

                y_out = jax.lax.ppermute(y, axis, fwd_perm)
                dx_out = jax.lax.ppermute(dx, axis, bwd_perm)
                return (y_out, dx_out, buf, dpar, dxs), None

            (_, _, _, dpar, dxs), _ = jax.lax.scan(
                tick, carry0, jnp.arange(T))
            if msh:
                dxs = dxs[None]        # restore the sharded leading dim
            else:
                dxs = jax.lax.psum(
                    jnp.where(d == 0, dxs, jnp.zeros_like(dxs)), axis)
            dpar = jax.tree_util.tree_map(lambda g: g[None], dpar)
            return dpar, dxs

        # check_vma=False: this IS the backward — no AD flows through it,
        # so vma tracking buys nothing and would reject the masked
        # device-varying selects
        res_spec = shard_res if msh else rep
        dxs_s = shard_map(
            per_device, mesh=mesh,
            in_specs=(pspec, res_spec, res_spec), out_specs=(pspec,
                                                            res_spec),
            check_vma=False, **kw,
        )
        dpar, dxs = dxs_s(params, res_mb,
                          reshard(dys) if msh else dys)
        if msh:
            dxs = dxs.reshape((M,) + dxs.shape[2:])
        return dpar, dxs

    pipe.defvjp(pipe_fwd, pipe_bwd)
    return pipe(stage_params, microbatches)


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage pytrees (same structure) into one pytree
    with a leading stage dim — the layout ``spmd_pipeline`` consumes."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params)


def shard_stacked_params(stacked, mesh, axis="pp"):
    """Place stage-stacked params with the leading dim over ``axis``."""
    def put(x):
        spec = P(axis, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(put, stacked)


def ps_delta_sync(ps, params, snapshot):
    """HetPipe's PS merge protocol (reference pipedream_subexecutor.py:
    317-328): push the delta accumulated since the last sync (the server
    ADDS pushes into its copy), pull the merged view, rebase.

    ``params``: name-keyed numpy-able dict of current worker weights.
    ``snapshot``: previous merged view, or None on the first sync — then
    each key is seeded idempotently (exactly one worker wins the init and
    pushes its full weights; a bare accumulate-push would sum every
    worker's weights).  Works against PSServer (param_init) and PSClient
    (parameter_init).  Returns (merged_params, new_snapshot)."""
    init = getattr(ps, "param_init", None) or \
        getattr(ps, "parameter_init", None)
    merged_out, snap_out = {}, {}
    first = snapshot is None
    for k, v in params.items():
        arr = np.asarray(v)
        if first:
            created = init(k, arr.shape) if init is not None else True
            if created:
                ps.push(k, arr)
        else:
            ps.push(k, arr - snapshot[k])
        merged = np.asarray(ps.pull(k)).copy()
        merged_out[k] = merged
        snap_out[k] = merged
    return merged_out, snap_out


# --------------------------------------------------------------------------- #
# 2. Explicit schedules (reference-parity orderings)
# --------------------------------------------------------------------------- #

FWD, BWD = "fwd", "bwd"


def gpipe_schedule(num_microbatches, stage_id=0, num_stages=1):
    """All-forward-then-all-backward (gpipe_subexecutor.py:33-111)."""
    order = [(m, FWD) for m in range(num_microbatches)]
    order += [(m, BWD) for m in reversed(range(num_microbatches))]
    return order


def one_f_one_b_schedule(num_microbatches, stage_id, num_stages):
    """1F1B: warmup fwds = num_stages - stage_id - 1, then alternate,
    then drain (the reference's generator, pipedream_subexecutor.py:25-48)."""
    warmup = min(num_stages - stage_id - 1, num_microbatches)
    order = [(m, FWD) for m in range(warmup)]
    f, b = warmup, 0
    while b < num_microbatches:
        if f < num_microbatches:
            order.append((f, FWD))
            f += 1
        order.append((b, BWD))
        b += 1
    return order


# backward-compat aliases matching reference naming
GPipeSchedule = gpipe_schedule
OneFOneBSchedule = one_f_one_b_schedule


# --------------------------------------------------------------------------- #
# 3. Host-loop pipeline trainer (semantics oracle / heterogeneous stages)
# --------------------------------------------------------------------------- #

@dataclass
class PipelineStage:
    """One stage: ``apply(params, x) -> y`` plus its parameter pytree."""
    apply: callable
    params: dict


class PipelineTrainer:
    """Drives heterogeneous stages through a schedule on the host.

    This is the semantics oracle / heterogeneous-stage path: fwd/bwd run
    eagerly as one vjp per microbatch (the vjp closes over forward-time
    weights, which is exactly PipeDream weight stashing).  The production
    TPU path is ``spmd_pipeline`` — one jitted XLA program.  Modes:

    - 'gpipe':     all-fwd-then-all-bwd, one optimizer step per batch
                   (reference SubExecutor4Gpipe).
    - 'pipedream': 1F1B with per-in-flight-microbatch weight stashing and
                   per-microbatch updates (reference SubExecutor4Pipedream,
                   copy_latest_weight :130-147).
    - '1f1b':      synchronous 1F1B — 1F1B order, grads accumulated, single
                   update (what modern frameworks ship; same math as gpipe,
                   less peak memory).
    - 'hetpipe':   local per-microbatch updates + push accumulated delta to
                   a PS every ``sync_every`` batches (reference :317-328).
    """

    def __init__(self, stages, optimizer=None, mode="gpipe",
                 loss_fn=None, sync_every=None, ps=None):
        self.stages = stages
        self.mode = mode
        # any hetu_tpu Optimizer (update_one/init_state_one); None = SGD 0.1
        self.optimizer = optimizer
        self.loss_fn = loss_fn  # (y_last, labels) -> scalar
        self.sync_every = sync_every
        self.ps = ps
        self._batches_seen = 0
        self._opt_states = None
        self._opt_step = 0
        # HetPipe pushes *deltas* since the last sync (the PS accumulates
        # pushes into the param, ps/server.py push); snapshot the baseline
        self._ps_snapshot = None

    def train_batch(self, microbatches, labels):
        """One global batch as M microbatches.  Returns mean loss.

        The vjp closure captures forward-time weights, which IS PipeDream
        weight stashing: in 'pipedream'/'hetpipe' mode ``live`` advances
        between microbatches, so each backward runs against the weights
        its forward saw (reference copy_latest_weight semantics)."""
        M = len(microbatches)
        S = len(self.stages)
        mode = self.mode
        sched = (gpipe_schedule if mode == "gpipe"
                 else one_f_one_b_schedule)(M, 0, S)
        losses = []
        live = [st.params for st in self.stages]
        accum = [jax.tree_util.tree_map(jnp.zeros_like, st.params)
                 for st in self.stages]
        inflight = {}
        for (m, direction) in sched:
            if direction == FWD:
                inflight[m] = self._fwd_loss(live, microbatches[m], labels[m])
            else:
                loss, vjp = inflight.pop(m)
                losses.append(loss)
                grads, _ = vjp(jnp.ones(()))
                if mode in ("pipedream", "hetpipe"):
                    live = self._apply_update(live, grads)
                else:
                    accum = [jax.tree_util.tree_map(jnp.add, a, g)
                             for a, g in zip(accum, grads)]
        if mode not in ("pipedream", "hetpipe"):
            scale = 1.0 / M
            accum = [jax.tree_util.tree_map(lambda g: g * scale, a)
                     for a in accum]
            live = self._apply_update(live, accum)
        for st, p in zip(self.stages, live):
            st.params = p
        self._batches_seen += 1
        if mode == "hetpipe" and self.ps is not None and self.sync_every and \
                self._batches_seen % self.sync_every == 0:
            self._ps_sync()
        return float(np.mean([np.asarray(l) for l in losses]))

    # -- helpers --------------------------------------------------------- #

    def _fwd_loss(self, params_per_stage, x, y_true):
        def full(params_list, x):
            h = x
            for st, p in zip(self.stages, params_list):
                h = st.apply(p, h)
            return self.loss_fn(h, y_true)
        loss, vjp = jax.vjp(full, list(params_per_stage), x)
        return loss, vjp

    def _apply_update(self, live, grads):
        opt = self.optimizer
        clip = getattr(opt, "clip_grad_norm", None) \
            if opt is not None else None
        if clip is not None:
            # same global-norm clip the Executor path applies in
            # OptimizerOp.apply — across ALL stages' gradient leaves
            if clip <= 0:
                raise ValueError(
                    f"clip_grad_norm must be positive, got {clip}")
            sq = jnp.asarray(0.0, jnp.float32)
            for gr in grads:
                for g in jax.tree_util.tree_leaves(gr):
                    sq = sq + jnp.sum(g.astype(jnp.float32) ** 2)
            factor = jnp.minimum(1.0, clip / (jnp.sqrt(sq) + 1e-6))
            grads = [jax.tree_util.tree_map(
                lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype),
                gr) for gr in grads]
        if opt is None or not hasattr(opt, "update_one"):
            lr = getattr(opt, "learning_rate", 0.1) if opt is not None else 0.1
            return [jax.tree_util.tree_map(lambda p, g: p - lr * g, pl, gr)
                    for pl, gr in zip(live, grads)]
        if self._opt_states is None:
            self._opt_states = [
                [opt.init_state_one(p)
                 for p in jax.tree_util.tree_leaves(pl)]
                for pl in live]
        step = jnp.asarray(self._opt_step, jnp.int32)
        lr = opt.lr_value(step)
        new_live = []
        for s_idx, (pl, gr) in enumerate(zip(live, grads)):
            flat_p, treedef = jax.tree_util.tree_flatten(pl)
            flat_g = treedef.flatten_up_to(gr)
            new_p, new_s = [], []
            for p, g, s in zip(flat_p, flat_g, self._opt_states[s_idx]):
                np_, ns_ = opt.update_one(p, g, s, lr, step)
                new_p.append(np_)
                new_s.append(ns_)
            self._opt_states[s_idx] = new_s
            new_live.append(jax.tree_util.tree_unflatten(treedef, new_p))
        self._opt_step += 1
        return new_live

    def _ps_sync(self):
        """HetPipe PS merge (shared protocol: ps_delta_sync above)."""
        flat = {f"stage{i}/{k}": v
                for i, st in enumerate(self.stages)
                for k, v in st.params.items()}
        merged, self._ps_snapshot = ps_delta_sync(
            self.ps, flat, self._ps_snapshot)
        for i, st in enumerate(self.stages):
            for k in st.params:
                st.params[k] = jnp.asarray(merged[f"stage{i}/{k}"])
