"""Device mesh construction — the spine of every parallelism strategy.

Replaces the reference's NCCL communicator bootstrap
(communicator/mpi_nccl_comm.py:62-250: MPI init, hashed group ids,
sub-communicators per DeviceGroup).  On TPU a single `jax.sharding.Mesh`
with named axes ('dp','tp','pp','ep','cp' over ICI; 'dcn' over multi-slice)
subsumes all communicator groups: collectives are axis-name-addressed and
XLA routes them over the right interconnect.

Multi-host bring-up is `jax.distributed.initialize()` (replacing
`wrapped_mpi_nccl_init`, executor.py:60-71).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np
import jax
from jax.sharding import Mesh


# canonical axis order: dcn-ish outermost, fastest-varying innermost so that
# tp/cp (highest-bandwidth-need) axes map to adjacent ICI neighbors
AXIS_ORDER = ("dcn", "pp", "dp", "ep", "cp", "tp")


@dataclass
class MeshAxes:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1
    cp: int = 1
    dcn: int = 1

    def total(self):
        return self.dp * self.tp * self.pp * self.ep * self.cp * self.dcn


def local_device_count():
    return jax.local_device_count()


def make_mesh(axes=None, devices=None, **kwargs):
    """Build a Mesh from axis sizes.  ``axes`` may be a MeshAxes, a dict
    {'dp': 4, 'tp': 2}, or kwargs.  Size -1 on one axis means "all remaining
    devices"."""
    if axes is None:
        axes = kwargs
    if isinstance(axes, MeshAxes):
        axes = {k: getattr(axes, k) for k in
                ("dcn", "pp", "dp", "ep", "cp", "tp")}
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    sizes = {k: int(v) for k, v in axes.items()}
    # resolve a single -1
    known = math.prod(v for v in sizes.values() if v > 0)
    for k, v in sizes.items():
        if v == -1:
            sizes[k] = n // known
    names = [a for a in AXIS_ORDER if sizes.get(a, 1) > 1]
    # axes outside the canonical set (e.g. 'ici' for hierarchical A2A)
    # append innermost in caller order
    names += [a for a in sizes if a not in AXIS_ORDER and sizes[a] > 1]
    if not names:
        names = [next(iter(sizes))] if sizes else ["dp"]
    dims = [sizes.get(a, 1) for a in names]
    total = math.prod(dims)
    assert total <= n, f"mesh {dict(zip(names, dims))} needs {total} devices, have {n}"
    arr = np.array(devices[:total]).reshape(dims)
    return Mesh(arr, tuple(names))


def default_mesh(dp=None):
    """All local devices on one 'dp' axis (the AllReduce-DP default,
    reference DataParallel strategy simple.py:6-39)."""
    n = dp or jax.device_count()
    return make_mesh({"dp": n})
