"""Device placement & cluster-config API.

TPU-native re-imagining of the reference's ``python/hetu/context.py``
(DeviceGroup at context.py:19, ``context()`` stack at context.py:174,
DistConfig at context.py:284).  On TPU, per-op device placement is replaced
by sharding annotations over a ``jax.sharding.Mesh``; this module keeps the
user-facing API (``with ht.context(...)``, ``DeviceGroup``, ``DistConfig``)
and maps it onto mesh-axis hints consumed by the executor.
"""

from __future__ import annotations

import contextlib
import re
import threading

import jax


class DLContext:
    """A logical device handle, API-compatible with the reference's DLContext
    (src/common/dlarray.h:44-52) but naming TPU cores.

    ``device_type`` is one of 'cpu', 'tpu' ('gpu' accepted as an alias for
    tpu so reference example scripts run unchanged), with an integer
    ``device_id``.  ``hostname`` supports the reference's rcpu/rgpu remote
    contexts (ndarray.py:22-60) and is used only for multi-host placement
    hints.
    """

    __slots__ = ("device_type", "device_id", "hostname")

    def __init__(self, device_type: str, device_id: int = 0, hostname: str = "localhost"):
        if device_type == "gpu":
            device_type = "tpu"
        self.device_type = device_type
        self.device_id = int(device_id)
        self.hostname = hostname

    @property
    def local(self) -> bool:
        return self.hostname in ("localhost", "127.0.0.1")

    def is_accelerator(self) -> bool:
        return self.device_type == "tpu"

    def relocalize(self):
        self.hostname = "localhost"

    def __eq__(self, other):
        return (
            isinstance(other, DLContext)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
            and self.hostname == other.hostname
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id, self.hostname))

    def __repr__(self):
        prefix = "" if self.local else self.hostname + ":"
        return f"{prefix}{self.device_type}({self.device_id})"


def cpu(dev_id: int = 0) -> DLContext:
    return DLContext("cpu", dev_id)


def tpu(dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id)


# alias so reference scripts using ht.gpu(i) work verbatim
def gpu(dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id)


def rcpu(hostname: str, dev_id: int = 0) -> DLContext:
    return DLContext("cpu", dev_id, hostname=hostname)


def rgpu(hostname: str, dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id, hostname=hostname)


def rtpu(hostname: str, dev_id: int = 0) -> DLContext:
    return DLContext("tpu", dev_id, hostname=hostname)


def is_gpu_ctx(ctx) -> bool:
    return isinstance(ctx, DLContext) and ctx.is_accelerator()


_CTX_PATTERN = re.compile(r"(?:(?P<host>[\w.\-]+):)?(?P<type>\w+)(?::|\()(?P<id>\d+)\)?")


def str2ctx(s: str) -> DLContext:
    m = _CTX_PATTERN.fullmatch(s.strip())
    assert m, f"cannot parse context string: {s!r}"
    host = m.group("host") or "localhost"
    return DLContext(m.group("type"), int(m.group("id")), hostname=host)


class DeviceGroup:
    """An ordered group of device contexts an op is placed on.

    Mirrors the reference's DeviceGroup (context.py:19-114): a flat list of
    contexts means replication (data parallel); a *tuple entry* means a
    model-parallel split across that tuple.  The TPU executor interprets a
    DeviceGroup of size k as "this op lives on a k-wide mesh slice"; actual
    partitioning comes from sharding specs, so the group mostly conveys
    (dp_degree, mp_degree, pipeline stage identity).
    """

    def __init__(self, ctxs):
        self._contexts = self._parse_contexts(ctxs)
        workers = []
        self._mp = False
        for c in self._contexts:
            if isinstance(c, tuple):
                self._mp = True
                workers.append(c)
            else:
                workers.append((c,))
        self._workers = tuple(workers)

    @staticmethod
    def _parse_contexts(ctxs):
        if isinstance(ctxs, DeviceGroup):
            return ctxs._contexts
        if isinstance(ctxs, str):
            parsed = []
            for part in ctxs.split(";"):
                part = part.strip()
                if not part:
                    continue
                if "," in part:
                    parsed.append(tuple(str2ctx(p) for p in part.split(",") if p.strip()))
                else:
                    parsed.append(str2ctx(part))
            return tuple(parsed)
        if isinstance(ctxs, DLContext):
            return (ctxs,)
        if isinstance(ctxs, (list, tuple)) and all(isinstance(c, DLContext) for c in ctxs):
            # plain list = replica group
            return tuple(ctxs)
        out = []
        for c in ctxs:
            if isinstance(c, (list, tuple)):
                out.append(tuple(c))
            elif isinstance(c, str):
                out.append(str2ctx(c))
            else:
                out.append(c)
        return tuple(out)

    @property
    def worker_num(self) -> int:
        return len(self._workers)

    @property
    def mp_degree(self) -> int:
        return max(len(w) for w in self._workers)

    @property
    def is_mp(self) -> bool:
        return self._mp

    def flat(self):
        for w in self._workers:
            yield from w

    def __len__(self):
        return len(self._workers)

    def __iter__(self):
        return iter(self._contexts)

    def __getitem__(self, i):
        return self._contexts[i]

    def __eq__(self, other):
        return isinstance(other, DeviceGroup) and self._contexts == other._contexts

    def __hash__(self):
        return hash(self._contexts)

    def __repr__(self):
        return f"DeviceGroup{self._contexts}"


class _ContextStack(threading.local):
    def __init__(self):
        super().__init__()
        self.stack = []

    def peek(self):
        return self.stack[-1] if self.stack else None

    def push(self, ctx):
        self.stack.append(ctx)

    def pop(self):
        self.stack.pop()


_ctx_stack = _ContextStack()


def get_current_context():
    return _ctx_stack.peek()


@contextlib.contextmanager
def context(ctx):
    """``with ht.context(tpu(0)): ...`` — reference context.py:174-181.

    Accepts a DLContext, a DeviceGroup, a string spec, or a list/tuple; ops
    built inside the block record the group as ``raw_ctx`` and the executor
    turns it into stage/shard hints.
    """
    if not isinstance(ctx, DeviceGroup):
        ctx = DeviceGroup(ctx)
    _ctx_stack.push(ctx)
    try:
        yield ctx
    finally:
        _ctx_stack.pop()


def check_worker(ctx) -> bool:
    return isinstance(ctx, (DeviceGroup, DLContext))


class DistConfig:
    """Cluster config loaded from yaml, reference context.py:284-366.

    The reference spawns PS scheduler/servers and mpirun workers from this;
    on TPU the worker topology comes from ``jax.distributed`` and this object
    mainly describes the (optional) parameter-server processes for the
    embedding path plus per-host worker counts for multi-host meshes.
    """

    def __init__(self, file=None, num_hosts=1, num_servers=0, num_workers=None):
        if file is not None:
            import yaml

            with open(file) as f:
                settings = yaml.safe_load(f)
            nodes = settings.get("nodes", [])
            self.hosts = []
            self.servers = {}
            self.workers = {}
            self.chief = None
            for node in nodes:
                host = node.get("host", "localhost")
                self.hosts.append(host)
                if node.get("servers"):
                    self.servers[host] = int(node["servers"])
                if node.get("workers"):
                    self.workers[host] = int(node["workers"])
                if node.get("chief", False):
                    self.chief = host
            if self.chief is None and self.hosts:
                self.chief = self.hosts[0]
            self.enable_PS = sum(self.servers.values()) > 0
        else:
            self.hosts = ["localhost"] * num_hosts
            self.chief = "localhost"
            self.servers = {"localhost": num_servers} if num_servers else {}
            if num_workers is None:
                num_workers = max(1, jax.local_device_count())
            self.workers = {"localhost": num_workers}
            self.enable_PS = num_servers > 0

    @property
    def num_workers(self) -> int:
        return sum(self.workers.values())

    @property
    def num_servers(self) -> int:
        return sum(self.servers.values())

    def __repr__(self):
        return (
            f"DistConfig(hosts={self.hosts}, chief={self.chief}, "
            f"servers={self.servers}, workers={self.workers})"
        )
